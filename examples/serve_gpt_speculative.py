"""Speculative decoding in the serving engine: n-gram drafting +
multi-token paged verification (README "Speculative decoding").

A small GPT is overfit on a cyclic token stream so greedy decode emits
genuinely repetitive output — the workload prompt-lookup drafting exists
for.  The same requests then run through the engine twice:

- baseline: ``ServingEngine(model, ...)`` — one token per decode dispatch;
- speculative: ``ServingEngine(model, ..., speculative_k=4)`` — up to 4
  n-gram-drafted tokens verified per dispatch, 1..5 tokens emitted.

Greedy outputs are asserted byte-identical; the side-by-side tokens/sec
and the measured acceptance rate print at the end.

Run (CPU works; a TPU runs the Pallas paged-attention kernel):

    JAX_PLATFORMS=cpu python examples/serve_gpt_speculative.py
"""

import time

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.serving import ServingEngine
from paddle_tpu.text.models import GPTForCausalLM


def build_repetitive_model(period=8, train_steps=150):
    """Overfit a small GPT on phase-shifted cycles: the model learns to
    continue the CONTEXT's cycle (phases vary across rows, so absolute
    positions don't give the answer away)."""
    paddle.seed(0)
    m = GPTForCausalLM(vocab_size=128, hidden_size=128, num_hidden_layers=4,
                       num_attention_heads=4, max_position_embeddings=256)
    cyc = (np.arange(256 + 64) % period + 1).astype("int64")
    o = opt.AdamW(learning_rate=3e-3, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, o, loss_fn=None)
    ids = paddle.to_tensor(np.stack([cyc[i:i + 64] for i in range(8)]))
    for _ in range(train_steps):
        step({"input_ids": ids, "labels": ids})
    return m.eval(), cyc, period


def run_engine(model, prompts, max_new, speculative_k):
    engine = ServingEngine(model, num_slots=4, page_size=16,
                           max_model_len=prompts[0].shape[0] + max_new,
                           speculative_k=speculative_k)
    with engine:
        engine.generate(prompts[0], max_new_tokens=4, timeout=600)  # compile
        t0 = time.time()
        handles = [engine.submit(p, max_new_tokens=max_new) for p in prompts]
        outs = [h.result(timeout=600) for h in handles]
        dt = time.time() - t0
        rate = engine.acceptance_rate
        spec = engine.stats().get("speculative")
    return outs, len(prompts) * max_new / dt, rate, spec


def main():
    print("overfitting a small GPT on a cyclic stream ...")
    model, cyc, period = build_repetitive_model()
    S0, max_new = 32, 96
    prompts = [cyc[i % period:i % period + S0] for i in range(8)]

    print("baseline engine (1 token / dispatch) ...")
    base, base_tps, _, _ = run_engine(model, prompts, max_new,
                                      speculative_k=0)
    print("speculative engine (k=4 n-gram drafts / dispatch) ...")
    spec, spec_tps, rate, st = run_engine(model, prompts, max_new,
                                          speculative_k=4)

    assert base == spec, "greedy outputs must be byte-identical"
    print(f"\nbaseline     : {base_tps:8.1f} tok/s")
    print(f"speculative  : {spec_tps:8.1f} tok/s  "
          f"({spec_tps / base_tps:.2f}x)")
    print(f"acceptance   : {rate:.3f}  "
          f"({st['accepted']}/{st['proposed']} drafts)")
    print("greedy outputs byte-identical: OK")


if __name__ == "__main__":
    main()
