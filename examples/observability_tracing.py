"""Distributed tracing + forensics walkthrough (paddle_tpu.observability).

Runs on the CPU backend: serves a few requests through the continuous-
batching engine with span tracing armed, exports the per-rank trace,
merges it with a profiler trace into one clock-aligned timeline, writes
OTLP JSON, trips the collective watchdog with an injected hang, and
scrapes the live /metrics | /healthz | /statusz endpoint.

    JAX_PLATFORMS=cpu python examples/observability_tracing.py
"""

import json
import os
import tempfile
import urllib.request

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.serving import ServingEngine
from paddle_tpu.text.models.gpt import GPTForCausalLM

out_dir = tempfile.mkdtemp(prefix="paddle_obs_")
print(f"artifacts -> {out_dir}")

# ---------------------------------------------------------------- tracing
paddle.seed(0)
model = GPTForCausalLM(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                       num_attention_heads=2,
                       max_position_embeddings=64).eval()

tracer = obs.Tracer().start()
engine = ServingEngine(model, num_slots=2, page_size=8, max_model_len=64,
                       telemetry_port=0)   # 0 = ephemeral live endpoint
with engine:
    handles = [engine.submit([1 + i, 2, 3, 4], max_new_tokens=4)
               for i in range(3)]
    for h in handles:
        h.result(timeout=600)

    # ---------------------------------------------------- live telemetry
    srv = obs.telemetry.get_server()
    for route in ("/healthz", "/statusz"):
        body = urllib.request.urlopen(srv.url + route, timeout=10).read()
        print(route, "->", body[:120], "…")
    prom = urllib.request.urlopen(srv.url + "/metrics", timeout=10).read()
    print("/metrics lines:", len(prom.decode().splitlines()))
tracer.stop()

for h in handles:
    spans = tracer.find(trace_id=h.trace_id)
    print(f"request {h.request_id}: trace {h.trace_id[:8]}… "
          f"{[s.name for s in spans]}")
steps = tracer.find("serving.decode_step")
print(f"{len(steps)} decode iterations, each linking its active requests")

rank_trace = tracer.export_chrome(os.path.join(out_dir, "rank0_spans.json"))
otlp = tracer.export_otlp(os.path.join(out_dir, "rank0_otlp.json"))

# --------------------------------------------- cross-rank merged timeline
merged = obs.merge_rank_traces([rank_trace],
                               out_path=os.path.join(out_dir, "merged.json"))
print("merged timeline events:", len(merged["traceEvents"]),
      "| OTLP:", otlp)

# ------------------------------------------- watchdog + flight recorder
import paddle_tpu.distributed as dist

obs.flight_recorder.enable(dir=os.path.join(out_dir, "flight"))
x = paddle.to_tensor(np.ones((8, 4), "float32"))
dist.all_reduce(x)          # warm: first dispatch = compile, not watchdogged
wd = obs.CollectiveWatchdog(deadline_s=0.3, poll_s=0.05).start()
obs.faults.inject("collective_hang", seconds=1.0)
dist.all_reduce(x)          # hangs ~1s; the watchdog fires at 0.3s
obs.faults.clear()
wd.stop()
fire = wd.fired[0]
print(f"watchdog fired: op={fire['op']} missing ranks={fire['ranks_missing']}")
dump = json.load(open(fire["dump_path"]))
print("flight record:", fire["dump_path"],
      "| open spans at dump:", [s["name"] for s in dump["open_spans"]])
obs.flight_recorder.disable()
obs.telemetry.shutdown()
