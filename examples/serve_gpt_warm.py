"""Warm restarts: the compile ledger + warmup manifests
(README "Program lifecycle & warmup").

A serving process's first request per program-store key pays the full
trace + XLA compile stall — tens of seconds for real models.  This demo
runs the SAME tiny GPT through a cold restart and a warm restart:

- cold: a fresh engine serves one request; its TTFT decomposition
  (``RequestHandle.ttft_breakdown()``) shows where the time went
  (``queue_s / compile_s / prefill_s``), the process-wide
  :class:`~paddle_tpu.observability.programs.ProgramLedger` shows every
  minted program with its compile wall and the trace id that paid it,
  and ``engine.capture_manifest()`` saves the store's key set;
- warm: a second engine over a fresh same-seed model replays the
  manifest with ``engine.warmup(path)`` BEFORE admission, so its first
  real request dispatches with ZERO new traces, ``compile_s == 0`` and
  byte-identical greedy output.

In production the manifest is captured once from a long-lived replica
and replayed on every restart / scale-up
(``ReplicaPool(model, warmup="manifest.json", ...)``), turning the
cold-start TTFT cliff into a deploy-time cost.

Run (CPU-friendly; compiles are ~1s here, minutes on real models):

    JAX_PLATFORMS=cpu python examples/serve_gpt_warm.py
"""

import json
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.observability import programs
from paddle_tpu.serving import ServingEngine
from paddle_tpu.text.models import GPTForCausalLM

PAGE = 16
S0, MAX_NEW = 32, 48


def build_model():
    paddle.seed(0)
    return GPTForCausalLM(vocab_size=128, hidden_size=128,
                          num_hidden_layers=4, num_attention_heads=4,
                          max_position_embeddings=256).eval()


def serve_one(engine, prompt):
    with engine:
        h = engine.submit(prompt, max_new_tokens=MAX_NEW)
        ids = list(h.result(timeout=600))
    return ids, h.ttft_breakdown()


def main():
    prompt = np.random.RandomState(0).randint(1, 128, (S0,)).tolist()
    manifest_path = os.path.join(tempfile.gettempdir(),
                                 "gpt_warm_manifest.json")

    # ---------------------------------------------------- cold restart
    print("=== cold restart: first request pays the compiles ===")
    model = build_model()
    engine = ServingEngine(model, num_slots=4, page_size=PAGE,
                           max_model_len=S0 + MAX_NEW)
    cold_ids, cold_bd = serve_one(engine, prompt)
    print(f"TTFT {cold_bd['ttft_s']:.3f}s = queue {cold_bd['queue_s']:.4f}s"
          f" + compile {cold_bd['compile_s']:.3f}s"
          f" + prefill {cold_bd['prefill_s']:.4f}s"
          f"  (cold={cold_bd['cold']})")

    led = programs.ledger()
    led.resolve_analysis()  # trace vs backend-compile split, exe size
    print("\nprogram ledger (the /statusz 'programs' table):")
    for row in led.rows():
        print(f"  {row['family']:<22} {row['cold']:<5}"
              f" compile {row['compile_s'] or 0:.3f}s"
              f" backend {row.get('backend_compile_s', 0) or 0:.3f}s"
              f" paid-by {str(row['trace_id'])[:8]}")

    engine.capture_manifest().save(manifest_path)
    n_keys = len(json.load(open(manifest_path))["keys"])
    print(f"\ncaptured {n_keys}-key manifest -> {manifest_path}")

    # ---------------------------------------------------- warm restart
    print("\n=== warm restart: manifest replayed before admission ===")
    model2 = build_model()  # a fresh process would rebuild from checkpoint
    engine2 = ServingEngine(model2, num_slots=4, page_size=PAGE,
                            max_model_len=S0 + MAX_NEW)
    info = engine2.warmup(manifest_path)
    print(f"warmup replayed {info['warmed']} programs"
          f" in {info['seconds']:.2f}s (skipped {info['skipped']})")

    traces0 = engine2.program_traces()
    warm_ids, warm_bd = serve_one(engine2, prompt)
    warm_traces = engine2.program_traces() - traces0

    print(f"TTFT {warm_bd['ttft_s']:.4f}s, compile"
          f" {warm_bd['compile_s']:.1f}s, new traces {warm_traces}")
    print(f"\ncold TTFT {cold_bd['ttft_s']:.3f}s ->"
          f" warm TTFT {warm_bd['ttft_s']:.4f}s"
          f" ({cold_bd['ttft_s'] / max(warm_bd['ttft_s'], 1e-9):.0f}x)")
    assert warm_traces == 0, "warmed engine must not trace"
    assert warm_ids == cold_ids, "greedy output must be byte-identical"
    print("OK: zero traces after warmup, byte-identical greedy output")


if __name__ == "__main__":
    main()
