"""Custom Pallas op + quantization-aware training, end to end.

Demonstrates the round-4 extension surfaces:
1. paddle.register_op — install a user Pallas kernel as a first-class op
   (SURVEY.md §2.1 custom-operator row: the PD_BUILD_OP equivalent),
2. paddle.quantization.QAT — fake-quant fine-tuning with straight-through
   gradients,
3. both running inside ONE fused TrainStep XLA program.

Run (CPU): env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python examples/custom_op_and_quant.py
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.quantization import QAT, QuantConfig

INTERPRET = jax.default_backend() != "tpu"


# ---- 1. a user Pallas kernel: fused bias+gelu ----
def _bias_gelu_kernel(x_ref, b_ref, o_ref):
    x = x_ref[...] + b_ref[...]
    o_ref[...] = (x * 0.5 * (1.0 + jax.lax.erf(x * 0.70710678))).astype(o_ref.dtype)


def bias_gelu(x, b):
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        _bias_gelu_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=INTERPRET,
    )(x, jnp.broadcast_to(b, x.shape))


def bias_gelu_bwd(res, g):
    x, b = res
    z = x + b
    cdf = 0.5 * (1.0 + jax.lax.erf(z * 0.70710678))
    pdf = jnp.exp(-0.5 * z * z) * 0.3989422804
    dz = g * (cdf + z * pdf)
    return dz, dz.sum(tuple(range(dz.ndim - 1)))


paddle.register_op("fused_bias_gelu", bias_gelu, vjp=bias_gelu_bwd,
                   override=True)


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(32, 64, bias_attr=False)
        self.b1 = self.create_parameter([64], is_bias=True)
        self.fc2 = nn.Linear(64, 10)

    def forward(self, x):
        h = paddle.ops.fused_bias_gelu(self.fc1(x), self.b1)
        return self.fc2(h)


def main():
    paddle.seed(0)
    model = Net()
    # ---- 2. quantize for QAT (wraps Linear layers with fake-quanters) ----
    model = QAT(QuantConfig()).quantize(model)
    o = opt.AdamW(learning_rate=3e-3, parameters=model.parameters())
    # ---- 3. one fused step: fwd (pallas + fake-quant) + bwd + update ----
    step = paddle.jit.TrainStep(model, o, loss_fn=nn.CrossEntropyLoss())

    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(128, 32).astype("float32"))
    y = paddle.to_tensor(rs.randint(0, 10, (128,)).astype("int64"))
    for i in range(30):
        loss = step(x, y)
        if i % 10 == 0:
            print(f"step {i:3d} loss {float(loss):.4f}")
    print(f"final loss {float(loss):.4f}")

    from paddle_tpu.quantization import extract_scales

    scales = extract_scales(model)
    print(f"{len(scales)} calibrated quant scales, e.g.",
          dict(list(scales.items())[:2]))


if __name__ == "__main__":
    main()
